"""Extension tests — analogs of ``tests/extension_tests/test_checkpoint.py``
(dagger) and the evaluator tests (SURVEY.md section 4): save/GC/resume
round-trip; evaluator averages metrics; persistent-value allreduce.
"""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from chainermn_tpu import (
    create_communicator,
    create_multi_node_checkpointer,
    create_multi_node_evaluator,
)
from chainermn_tpu.extensions import AllreducePersistent, ObservationAggregator


@pytest.fixture(scope="module")
def comm():
    return create_communicator("naive")


def test_evaluator_passthrough_single_process(comm):
    ev = create_multi_node_evaluator(lambda: {"acc": 0.5, "loss": 2.0}, comm)
    out = ev()
    assert out == {"acc": 0.5, "loss": 2.0}


def test_evaluator_weighted_by_n(comm):
    ev = create_multi_node_evaluator(lambda: {"acc": 0.25, "n": 4}, comm)
    assert ev() == {"acc": 0.25}


def test_checkpointer_roundtrip(tmp_path, comm):
    ckpt = create_multi_node_checkpointer("job", comm, path=str(tmp_path))
    state = {"w": jnp.arange(6.0).reshape(2, 3), "step": jnp.int32(7)}
    ckpt.save(state, iteration=100)

    template = {"w": jnp.zeros((2, 3)), "step": jnp.int32(0)}
    restored, it = ckpt.maybe_load(template)
    assert it == 100
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.arange(6.0).reshape(2, 3))
    assert int(restored["step"]) == 7


def test_checkpointer_no_snapshot_returns_template(tmp_path, comm):
    ckpt = create_multi_node_checkpointer("fresh", comm, path=str(tmp_path))
    template = {"x": jnp.zeros(3)}
    restored, it = ckpt.maybe_load(template)
    assert it is None
    assert restored is template


def test_checkpointer_gc_keeps_newest(tmp_path, comm):
    ckpt = create_multi_node_checkpointer("gc", comm, path=str(tmp_path), keep=2)
    state = {"x": jnp.zeros(2)}
    for it in [1, 2, 3, 4, 5]:
        ckpt.save(state, iteration=it)
    files = sorted(os.listdir(tmp_path))
    assert files == ["snapshot_gc_0_4.npz", "snapshot_gc_0_5.npz"]
    _, it = ckpt.maybe_load(state)
    assert it == 5


def test_checkpointer_resumes_max_common(tmp_path, comm):
    ckpt = create_multi_node_checkpointer("agree", comm, path=str(tmp_path), keep=10)
    state = {"x": jnp.ones(2)}
    ckpt.save(state, 10)
    ckpt.save(state, 20)
    _, it = ckpt.maybe_load(state)
    assert it == 20  # newest common (single process: newest local)


def test_checkpointer_keys_by_tree_path_not_position(tmp_path, comm):
    """Same-shaped leaves restore by NAME: a template whose dict ordering
    differs still gets each array at its right key (the positional
    ``leaf_{i}`` format silently mis-assigned here)."""
    ckpt = create_multi_node_checkpointer("paths", comm, path=str(tmp_path))
    state = {"alpha": jnp.full((2, 2), 1.0), "beta": jnp.full((2, 2), 2.0)}
    ckpt.save(state, 1)

    # dict insertion order differs; tree paths must still disambiguate
    template = {"beta": jnp.zeros((2, 2)), "alpha": jnp.zeros((2, 2))}
    restored, _ = ckpt.maybe_load(template)
    np.testing.assert_array_equal(np.asarray(restored["alpha"]), np.full((2, 2), 1.0))
    np.testing.assert_array_equal(np.asarray(restored["beta"]), np.full((2, 2), 2.0))


def test_checkpointer_renamed_leaf_fails_loudly(tmp_path, comm):
    ckpt = create_multi_node_checkpointer("rename", comm, path=str(tmp_path))
    ckpt.save({"w": jnp.zeros((3,)), "b": jnp.zeros((3,))}, 1)
    with pytest.raises(ValueError, match="key set"):
        ckpt.maybe_load({"w": jnp.zeros((3,)), "bias": jnp.zeros((3,))})


def test_checkpointer_shape_mismatch_fails_loudly(tmp_path, comm):
    ckpt = create_multi_node_checkpointer("shape", comm, path=str(tmp_path))
    ckpt.save({"w": jnp.zeros((3, 4))}, 1)
    with pytest.raises(ValueError, match="shape"):
        ckpt.maybe_load({"w": jnp.zeros((4, 3))})


def test_checkpointer_cleanup(tmp_path, comm):
    ckpt = create_multi_node_checkpointer("clean", comm, path=str(tmp_path))
    ckpt.save({"x": jnp.zeros(1)}, 1)
    ckpt.cleanup()
    assert os.listdir(tmp_path) == []


def test_allreduce_persistent_replicates(comm):
    ext = AllreducePersistent(comm)
    stats = {"mean": np.ones((4,), np.float32), "var": np.full((4,), 2.0, np.float32)}
    out = ext(stats)
    np.testing.assert_allclose(np.asarray(out["mean"]), stats["mean"])
    assert out["mean"].sharding.is_fully_replicated


def test_observation_aggregator(comm):
    agg = ObservationAggregator(comm)
    assert agg({"loss": 1.5}) == {"loss": 1.5}


def test_global_except_hook_installs():
    import sys

    from chainermn_tpu import global_except_hook

    old = sys.excepthook
    try:
        global_except_hook._add_hook()
        assert sys.excepthook is global_except_hook._global_except_hook
        global_except_hook._add_hook()  # idempotent
        assert sys.excepthook is global_except_hook._global_except_hook
    finally:
        sys.excepthook = old
        global_except_hook._hook_installed = False


def test_observation_aggregator_windowed(comm):
    """interval>1: calls buffer locally (None) until the window closes,
    then the window mean is aggregated — upstream ObservationAggregator
    semantics (time average, then cross-rank average)."""
    agg = ObservationAggregator(comm, interval=3)
    assert agg({"loss": 4.0}) is None
    assert agg({"loss": 2.0, "acc": 1.0}) is None
    out = agg({"loss": 0.0})
    # single process: mean over the window per key
    assert out == {"loss": 2.0, "acc": 1.0}
    # window state resets
    assert agg({"loss": 10.0}) is None


class TestAsyncCheckpoint:
    def test_async_save_roundtrip(self, comm, tmp_path):
        """block=False saves become durable at wait_async; maybe_load drains
        first, so an immediately-following restore sees them."""
        from chainermn_tpu.extensions.checkpoint import (
            create_multi_node_checkpointer,
        )

        ckpt = create_multi_node_checkpointer(
            "async", comm, path=str(tmp_path), keep=2
        )
        state = {"w": jnp.arange(6.0).reshape(2, 3), "step": jnp.int32(0)}
        for it in range(1, 5):
            ckpt.save({**state, "step": jnp.int32(it)}, it, block=False)
        ckpt.wait_async()
        # GC ran at drain: only `keep` newest snapshots remain
        files = sorted(p.name for p in tmp_path.iterdir())
        assert len(files) == 2, files
        restored, it = ckpt.maybe_load(state)
        assert it == 4 and int(restored["step"]) == 4
        np.testing.assert_allclose(
            np.asarray(restored["w"]), np.arange(6.0).reshape(2, 3)
        )

    def test_async_failure_surfaces_at_wait(self, comm, tmp_path):
        from chainermn_tpu.extensions.checkpoint import (
            create_multi_node_checkpointer,
        )
        import pytest

        ckpt = create_multi_node_checkpointer(
            "fail", comm, path=str(tmp_path), keep=0
        )
        state = {"w": jnp.zeros((2,))}
        ckpt.save(state, 1, block=False)
        ckpt.wait_async()
        # point the next write at a non-existent directory
        ckpt.path = str(tmp_path / "gone" / "deeper")
        ckpt.save(state, 2, block=False)
        with pytest.raises(RuntimeError, match="checkpoint write"):
            ckpt.wait_async()

    def test_writer_overlaps(self, tmp_path):
        """The writer really is asynchronous: submit returns while the data
        is still being made durable (bounded queue accepts ahead)."""
        from chainermn_tpu.native.ckpt_writer import AsyncCheckpointWriter

        w = AsyncCheckpointWriter(queue_depth=4)
        blob = b"x" * (32 << 20)
        for i in range(4):
            w.submit(str(tmp_path / f"f{i}.bin"), blob)
        # asynchrony pinned: 128 MB of fsync cannot all be durable by the
        # time the submits return — some work must still be pending.
        assert w.pending > 0
        w.wait()
        assert w.pending == 0
        for i in range(4):
            assert (tmp_path / f"f{i}.bin").stat().st_size == len(blob)
        w.finalize()


def test_async_writer_use_after_finalize_raises(tmp_path):
    from chainermn_tpu.native.ckpt_writer import AsyncCheckpointWriter
    import pytest

    w = AsyncCheckpointWriter()
    w.submit(str(tmp_path / "a.bin"), b"abc")
    w.wait()
    w.finalize()
    with pytest.raises(RuntimeError, match="after finalize"):
        w.submit(str(tmp_path / "b.bin"), b"abc")
    with pytest.raises(RuntimeError, match="after finalize"):
        w.wait()


# ---------------------------------------------------------------------------
# Orbax adapter
# ---------------------------------------------------------------------------


def test_orbax_checkpointer_roundtrip(tmp_path, comm):
    pytest.importorskip("orbax.checkpoint")
    from chainermn_tpu.extensions import create_orbax_checkpointer

    ckpt = create_orbax_checkpointer("job", comm, path=str(tmp_path))
    state = {"w": jnp.arange(6.0).reshape(2, 3), "step": jnp.int32(7)}
    ckpt.save(state, iteration=100)

    template = {"w": jnp.zeros((2, 3)), "step": jnp.int32(0)}
    restored, it = ckpt.maybe_load(template)
    assert it == 100
    np.testing.assert_array_equal(
        np.asarray(restored["w"]), np.arange(6.0).reshape(2, 3)
    )
    assert int(restored["step"]) == 7
    ckpt.close()


def test_orbax_checkpointer_empty_and_retention(tmp_path, comm):
    pytest.importorskip("orbax.checkpoint")
    from chainermn_tpu.extensions import create_orbax_checkpointer

    ckpt = create_orbax_checkpointer("ret", comm, path=str(tmp_path), keep=2)
    template = {"x": jnp.zeros(3)}
    restored, it = ckpt.maybe_load(template)
    assert it is None and restored is template

    for step in [1, 2, 3, 4, 5]:
        ckpt.save(template, iteration=step)
    assert ckpt._local_iterations() == [4, 5]
    _, it = ckpt.maybe_load(template)
    assert it == 5
    ckpt.close()


def test_orbax_checkpoints_readable_by_plain_orbax(tmp_path, comm):
    """Interop contract: what the adapter writes, stock orbax tooling
    reads (and the directory layout is plain CheckpointManager)."""
    ocp = pytest.importorskip("orbax.checkpoint")

    from chainermn_tpu.extensions import create_orbax_checkpointer

    ckpt = create_orbax_checkpointer("interop", comm, path=str(tmp_path))
    state = {"a": jnp.full((4,), 3.0)}
    ckpt.save(state, iteration=42)
    ckpt.close()

    mgr = ocp.CheckpointManager(ckpt.path)
    assert mgr.all_steps() == [42]
    out = mgr.restore(42, args=ocp.args.StandardRestore({"a": jnp.zeros(4)}))
    np.testing.assert_array_equal(np.asarray(out["a"]), np.full((4,), 3.0))
    mgr.close()


def test_orbax_checkpointer_resave_same_step_overwrites(tmp_path, comm):
    """Re-saving an iteration must overwrite (npz parity), not raise
    StepAlreadyExistsError — the resume-then-finish flow saves the final
    step twice."""
    pytest.importorskip("orbax.checkpoint")
    from chainermn_tpu.extensions import create_orbax_checkpointer

    ckpt = create_orbax_checkpointer("resave", comm, path=str(tmp_path))
    ckpt.save({"x": jnp.zeros(2)}, iteration=7)
    ckpt.save({"x": jnp.ones(2)}, iteration=7)
    restored, it = ckpt.maybe_load({"x": jnp.zeros(2)})
    assert it == 7
    np.testing.assert_array_equal(np.asarray(restored["x"]), np.ones(2))
    ckpt.close()


def test_orbax_restore_returns_host_arrays(tmp_path, comm):
    """Fully-addressable leaves come back as HOST arrays (npz parity) so
    the next jitted step re-places them — device-committed restores with
    leaf-to-leaf placement disagreements broke the first step after
    resume."""
    pytest.importorskip("orbax.checkpoint")
    from chainermn_tpu.extensions import create_orbax_checkpointer

    ckpt = create_orbax_checkpointer("host", comm, path=str(tmp_path))
    ckpt.save({"w": jnp.arange(4.0), "step": jnp.int32(3)}, iteration=1)
    restored, it = ckpt.maybe_load(
        {"w": jnp.zeros(4), "step": jnp.int32(0)}
    )
    assert it == 1
    assert isinstance(restored["w"], np.ndarray)
    assert isinstance(restored["step"], np.ndarray)
    ckpt.close()


def test_orbax_async_save_then_resave_same_step(tmp_path, comm):
    """An uncommitted async save of step N followed by a blocking resave
    of N must overwrite, not raise StepAlreadyExistsError (orbax commits
    the pending save inside save() — the TOCTOU the drain-first fixes)."""
    pytest.importorskip("orbax.checkpoint")
    from chainermn_tpu.extensions import create_orbax_checkpointer

    ckpt = create_orbax_checkpointer("toctou", comm, path=str(tmp_path))
    ckpt.save({"x": jnp.zeros(2)}, iteration=5, block=False)
    ckpt.save({"x": jnp.ones(2)}, iteration=5)  # same step, pending async
    restored, it = ckpt.maybe_load({"x": jnp.zeros(2)})
    assert it == 5
    np.testing.assert_array_equal(np.asarray(restored["x"]), np.ones(2))
    ckpt.close()


def test_global_from_shards_coverage_and_conflicts(tmp_path):
    """Unit pins for the world-resize reassembly: full coverage required,
    conflicting duplicate shards rejected."""
    import numpy as np

    from chainermn_tpu.extensions.checkpoint import MultiNodeCheckpointer

    full = np.arange(12, dtype=np.float32).reshape(6, 2)
    merged = {
        "w@@0:3|0:2": full[0:3],
        "w@@3:6|0:2": full[3:6],
    }
    out = MultiNodeCheckpointer._global_from_shards(
        "w", merged, (6, 2), np.float32
    )
    np.testing.assert_array_equal(out, full)

    import pytest

    with pytest.raises(ValueError, match="do not cover"):
        MultiNodeCheckpointer._global_from_shards(
            "w", {"w@@0:3|0:2": full[0:3]}, (6, 2), np.float32
        )
    with pytest.raises(ValueError, match="no shards"):
        MultiNodeCheckpointer._global_from_shards(
            "v", merged, (6, 2), np.float32
        )


def test_checkpointer_roundtrip_local_sgd_state(tmp_path, comm):
    """The round-5 LocalSGD optimizer state (inner chain + step counter +
    anchor + outer velocity, a nested NamedTuple pytree) survives the
    npz save/restore cycle with structure and values intact — resuming
    mid-window must keep the anchor, or the next sync's outer delta is
    computed against the wrong reference point."""
    import jax
    import optax

    from chainermn_tpu import create_local_sgd

    params = {"w": jnp.arange(4.0)}
    opt = create_local_sgd(optax.adam(0.1), comm, sync_every=4,
                           outer_momentum=0.9)
    state = opt.init(params)
    # advance one step so every field is non-trivial
    u, state = jax.jit(opt.update)(
        {"w": jnp.ones(4)}, state, params
    )
    ckpt = create_multi_node_checkpointer(
        "localsgd", comm, path=str(tmp_path)
    )
    ckpt.save({"opt": state}, iteration=11)

    template = {"opt": opt.init(params)}
    restored, it = ckpt.maybe_load(template)
    assert it == 11
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        restored["opt"], state,
    )
    assert int(restored["opt"].step) == 1


class TestStridedShardIndices:
    """ISSUE 10 satellite: `_index_str` supports STRIDED shard indices
    (``start:stop:step``) instead of asserting them away — the parse side
    (``slice(*map(int, part.split(':')))``) was already general, so the
    format change closes the loop end to end."""

    def test_index_str_contiguous_unchanged(self):
        from chainermn_tpu.extensions.checkpoint import _index_str

        assert _index_str((slice(0, 4), slice(None)), (8, 3)) == "0:4|0:3"

    def test_index_str_strided(self):
        from chainermn_tpu.extensions.checkpoint import _index_str

        assert _index_str((slice(0, 8, 2), slice(0, 4)), (8, 4)) \
            == "0:8:2|0:4"
        assert _index_str((slice(1, 8, 2),), (8,)) == "1:8:2"

    def test_global_from_shards_reassembles_strided(self):
        from chainermn_tpu.extensions.checkpoint import (
            MultiNodeCheckpointer,
            _SHARD_SEP,
        )

        full = np.arange(32.0).reshape(8, 4)
        merged = {
            f"w{_SHARD_SEP}0:8:2|0:4": full[0:8:2],
            f"w{_SHARD_SEP}1:8:2|0:4": full[1:8:2],
        }
        out = MultiNodeCheckpointer._global_from_shards(
            "w", merged, (8, 4), np.float32
        )
        np.testing.assert_array_equal(out, full)

    def test_global_from_shards_strided_hole_fails_loudly(self):
        from chainermn_tpu.extensions.checkpoint import (
            MultiNodeCheckpointer,
            _SHARD_SEP,
        )

        full = np.arange(32.0).reshape(8, 4)
        merged = {f"w{_SHARD_SEP}0:8:2|0:4": full[0:8:2]}  # odd rows missing
        with pytest.raises(ValueError, match="do not cover"):
            MultiNodeCheckpointer._global_from_shards(
                "w", merged, (8, 4), np.float32
            )
