"""Transformer LM and seq2seq LSTM tests, incl. the variable-length
bucketing discipline and a sequence-parallel (ring attention) LM run that
must match the single-device LM — the distributed == single-process
invariant (SURVEY.md section 4) on the language-model workloads
(BASELINE.json configs)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from chainermn_tpu.datasets.bucketing import (
    DEFAULT_BUCKETS,
    bucket_batches,
    bucket_length,
)
from chainermn_tpu.models import Seq2Seq, TransformerLM, lm_loss, seq2seq_loss

VOCAB = 64


def tiny_lm(**kw):
    cfg = dict(
        vocab_size=VOCAB, num_layers=2, num_heads=4, d_model=32, d_ff=64,
        max_len=64, compute_dtype=jnp.float32,
    )
    cfg.update(kw)
    return TransformerLM(**cfg)



def windowed_lm(window, **kw):
    """Tiny LM with a window-honouring flash attention_fn — shared by the
    windowed-decode and windowed-beam tests so both exercise the same
    attention configuration."""
    from chainermn_tpu.ops.flash_attention import flash_attention

    def attn(q, k, v, *, causal, scale):
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               window=window, block_q=16, block_k=16,
                               interpret=True)

    return tiny_lm(attention_fn=attn, window=window, **kw)


class TestTransformerLM:
    def test_shapes_and_loss(self):
        model = tiny_lm()
        tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 16), 0, VOCAB)
        params = model.init(jax.random.PRNGKey(1), tokens)
        logits = model.apply(params, tokens)
        assert logits.shape == (2, 16, VOCAB)
        loss = lm_loss(logits, tokens)
        assert np.isfinite(float(loss))

    def test_causality(self):
        """Changing future tokens must not change past logits."""
        model = tiny_lm()
        t1 = jax.random.randint(jax.random.PRNGKey(0), (1, 16), 0, VOCAB)
        t2 = t1.at[0, 10:].set((t1[0, 10:] + 1) % VOCAB)
        params = model.init(jax.random.PRNGKey(1), t1)
        l1 = model.apply(params, t1)
        l2 = model.apply(params, t2)
        np.testing.assert_allclose(l1[:, :10], l2[:, :10], rtol=1e-5, atol=1e-5)

    def test_training_reduces_loss(self):
        model = tiny_lm()
        tokens = jax.random.randint(jax.random.PRNGKey(0), (4, 16), 0, VOCAB)
        params = model.init(jax.random.PRNGKey(1), tokens)
        opt = optax.adam(1e-2)
        opt_state = opt.init(params)

        @jax.jit
        def step(params, opt_state):
            loss, grads = jax.value_and_grad(
                lambda p: lm_loss(model.apply(p, tokens), tokens)
            )(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        params2, opt_state, l0 = step(params, opt_state)
        for _ in range(10):
            params2, opt_state, ln = step(params2, opt_state)
        assert float(ln) < float(l0)

    def test_rope_model_trains_without_pos_table(self):
        """pos_encoding='rope': no pos_emb parameter, causality holds,
        loss decreases."""
        model = tiny_lm(pos_encoding="rope")
        tokens = jax.random.randint(jax.random.PRNGKey(0), (4, 16), 0, VOCAB)
        params = model.init(jax.random.PRNGKey(1), tokens)
        assert "pos_emb" not in params["params"]
        # causality
        t2 = tokens.at[:, 10:].set((tokens[:, 10:] + 1) % VOCAB)
        l1 = model.apply(params, tokens)
        l2 = model.apply(params, t2)
        np.testing.assert_allclose(l1[:, :10], l2[:, :10],
                                   rtol=1e-5, atol=1e-5)
        # The defining RoPE property: a UNIFORM shift of all positions
        # cancels in q·k (relative encoding) — logits are invariant...
        l3 = model.apply(params, tokens,
                         positions=jnp.arange(16, dtype=jnp.int32) + 5)
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l3),
                                   rtol=1e-4, atol=1e-4)
        # ...while a NON-uniform remapping (stretched gaps) changes them.
        l4 = model.apply(params, tokens,
                         positions=jnp.arange(16, dtype=jnp.int32) * 3)
        assert not np.allclose(np.asarray(l1), np.asarray(l4), atol=1e-3)
        # trains
        opt = optax.adam(1e-2)
        st = opt.init(params)

        @jax.jit
        def step(p, st):
            l, g = jax.value_and_grad(
                lambda p: lm_loss(model.apply(p, tokens), tokens))(p)
            u, st = opt.update(g, st, p)
            return optax.apply_updates(p, u), st, l

        p2, st, l0 = step(params, st)
        for _ in range(10):
            p2, st, ln = step(p2, st)
        assert float(ln) < float(l0)

    def test_rope_sequence_parallel_matches_single_device(self, comm):
        """RoPE + ring attention: per-shard GLOBAL positions reproduce
        the single-device logits — the modern-position-encoding analog of
        the learned-table rolling trick (no table to roll)."""
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        from chainermn_tpu.parallel.ring_attention import (
            ring_attention_local,
        )

        n = comm.size
        T = 4 * n

        def ring_attn(q, k, v, *, causal, scale):
            return ring_attention_local(q, k, v, "data", causal=causal,
                                        scale=scale)

        sp_model = tiny_lm(max_len=T, pos_encoding="rope",
                           attention_fn=ring_attn)
        ref_model = tiny_lm(max_len=T, pos_encoding="rope")
        tokens = jax.random.randint(jax.random.PRNGKey(2), (2, T), 0, VOCAB)
        params = ref_model.init(jax.random.PRNGKey(3), tokens)
        ref = ref_model.apply(params, tokens)

        def local(p, tok):
            t_local = tok.shape[1]
            idx = jax.lax.axis_index("data")
            pos = idx * t_local + jnp.arange(t_local, dtype=jnp.int32)
            return sp_model.apply(p, tok, positions=pos)

        out = jax.jit(
            shard_map(
                local, mesh=comm.mesh, in_specs=(P(), P(None, "data")),
                out_specs=P(None, "data"), check_vma=False,
            )
        )(params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_learned_positions_gather_matches_default(self):
        """positions= on the learned-table path gathers table rows: with
        the identity positions it equals the default slice (the SP
        example's per-shard form)."""
        model = tiny_lm()
        tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 16), 0, VOCAB)
        params = model.init(jax.random.PRNGKey(5), tokens)
        l_default = model.apply(params, tokens)
        l_pos = model.apply(params, tokens,
                            positions=jnp.arange(16, dtype=jnp.int32))
        np.testing.assert_allclose(np.asarray(l_pos), np.asarray(l_default),
                                   rtol=1e-6, atol=1e-6)
        # offset positions read different table rows
        l_off = model.apply(params, tokens,
                            positions=jnp.arange(16, dtype=jnp.int32) + 8)
        assert not np.allclose(np.asarray(l_off), np.asarray(l_default),
                               atol=1e-4)

    def test_gqa_model_trains_and_shrinks_kv(self):
        """num_kv_heads shrinks the qkv projection and still trains; MHA
        (num_kv_heads=num_heads) keeps the original 3*D parameter shape."""
        mha = tiny_lm()
        gqa = tiny_lm(num_kv_heads=2)
        tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 16), 0, VOCAB)
        p_mha = mha.init(jax.random.PRNGKey(1), tokens)
        p_gqa = gqa.init(jax.random.PRNGKey(1), tokens)
        w_mha = p_mha["params"]["block_0"]["qkv"]["kernel"]
        w_gqa = p_gqa["params"]["block_0"]["qkv"]["kernel"]
        assert w_mha.shape == (32, 3 * 32)
        # 4 q heads of 8 dims + 2*2 kv heads of 8 dims
        assert w_gqa.shape == (32, (4 + 4) * 8)
        loss = lm_loss(gqa.apply(p_gqa, tokens), tokens)
        assert np.isfinite(float(loss))
        g = jax.grad(lambda p: lm_loss(gqa.apply(p, tokens), tokens))(p_gqa)
        assert all(np.isfinite(x).all() for x in jax.tree.leaves(g))

    def test_packed_segments_confine_attention(self):
        """With segment ids, changing tokens of document 2 must not change
        logits inside document 1 (flash path; causality test's packed
        analog)."""
        from chainermn_tpu.ops.flash_attention import flash_attention

        def attn(q, k, v, *, causal, scale, segment_ids=None):
            return flash_attention(q, k, v, causal=causal, scale=scale,
                                   segment_ids=segment_ids, interpret=True)

        model = tiny_lm(attention_fn=attn)
        t1 = jax.random.randint(jax.random.PRNGKey(0), (1, 16), 0, VOCAB)
        seg = jnp.asarray([[0] * 8 + [1] * 8])
        t2 = t1.at[0, 8:].set((t1[0, 8:] + 3) % VOCAB)
        params = model.init(jax.random.PRNGKey(1), t1)
        l1 = model.apply(params, t1, segment_ids=seg)
        l2 = model.apply(params, t2, segment_ids=seg)
        np.testing.assert_allclose(l1[:, :8], l2[:, :8], rtol=1e-5, atol=1e-5)
        # and with no segment ids the same edit WOULD leak backward? No —
        # causal masking already stops past positions seeing the future;
        # the real packed hazard is doc 1 attending doc 0. Check the other
        # direction: change document 0, document 1's logits must ALSO stay
        # fixed (only possible because of the segment mask).
        t3 = t1.at[0, :8].set((t1[0, :8] + 5) % VOCAB)
        l3 = model.apply(params, t3, segment_ids=seg)
        np.testing.assert_allclose(l1[:, 8:], l3[:, 8:], rtol=1e-5, atol=1e-5)

    def test_segment_ids_require_capable_attention(self):
        model = tiny_lm()
        tokens = jax.random.randint(jax.random.PRNGKey(0), (1, 8), 0, VOCAB)
        params = model.init(jax.random.PRNGKey(1), tokens)
        with pytest.raises(ValueError, match="segment-capable"):
            model.apply(params, tokens, segment_ids=jnp.zeros((1, 8),
                                                              jnp.int32))

    def test_fused_lm_loss_matches_plain(self):
        """``lm_loss_fused`` on hidden states == ``lm_loss`` on the full
        logits (f32 compute so rounding cannot hide a real defect), for an
        uneven B*(T-1) that exercises the padded tail chunk — value AND
        gradients (the head is rematerialized in the backward)."""
        from chainermn_tpu.models import lm_loss_fused

        model = tiny_lm()
        hidden_model = tiny_lm(return_hidden=True)
        tokens = jax.random.randint(jax.random.PRNGKey(0), (3, 17), 0, VOCAB)
        params = model.init(jax.random.PRNGKey(1), tokens)

        def plain(p):
            return lm_loss(model.apply(p, tokens), tokens)

        def fused(p):
            h = hidden_model.apply(p, tokens)
            emb = p["params"]["tok_emb"]["embedding"]
            return lm_loss_fused(h, emb, tokens, n_chunks=4,
                                 compute_dtype=jnp.float32)

        l_plain, g_plain = jax.value_and_grad(plain)(params)
        l_fused, g_fused = jax.value_and_grad(fused)(params)
        np.testing.assert_allclose(float(l_fused), float(l_plain), rtol=1e-5)
        for a, b in zip(jax.tree.leaves(g_fused), jax.tree.leaves(g_plain)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
            )

    def test_remat_matches_plain(self):
        """``remat=True`` changes memory, never values: same logits and
        same gradients as the un-rematerialized model."""
        model = tiny_lm()
        rmodel = tiny_lm(remat=True)
        tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, VOCAB)
        params = model.init(jax.random.PRNGKey(3), tokens)
        np.testing.assert_allclose(
            np.asarray(model.apply(params, tokens)),
            np.asarray(rmodel.apply(params, tokens)),
            rtol=1e-6, atol=1e-6,
        )
        g1 = jax.grad(lambda p: lm_loss(model.apply(p, tokens), tokens))(params)
        g2 = jax.grad(lambda p: lm_loss(rmodel.apply(p, tokens), tokens))(params)
        for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            )

    def test_ring_attention_lm_matches_single_device(self, comm):
        """The same weights, run with ring attention over the 8-way sequence
        axis, must reproduce the single-device logits."""
        from chainermn_tpu.parallel.ring_attention import ring_attention_local

        T = 32
        tokens = jax.random.randint(jax.random.PRNGKey(0), (2, T), 0, VOCAB)
        ref_model = tiny_lm()
        params = ref_model.init(jax.random.PRNGKey(1), tokens)
        ref = ref_model.apply(params, tokens)

        mesh, ax = comm.mesh, comm.axis_name
        n = comm.size
        t_local = T // n

        def local(params, tokens_shard):
            idx = jax.lax.axis_index(ax)

            def ring_attn(q, k, v, *, causal, scale):
                return ring_attention_local(
                    q, k, v, ax, causal=causal, scale=scale
                )

            model = tiny_lm(attention_fn=ring_attn)
            return _apply_with_offset(model, params, tokens_shard, idx, t_local)

        out = jax.jit(
            shard_map(
                local, mesh=mesh,
                in_specs=(P(), P(None, ax)),
                out_specs=P(None, ax),
                check_vma=False,
            )
        )(params, tokens)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
        )


def _apply_with_offset(model, params, tokens_shard, idx, t_local):
    """Apply the LM on a sequence shard with learned-position offset
    idx*t_local. pos_offset is a static attribute, so instead we roll the
    table: slice positions dynamically by rebinding the embedding lookup."""
    import flax.linen as nn

    # Rebuild: take pos_emb rows [idx*t_local, idx*t_local + t_local)
    offset = idx * t_local

    def apply_fn(variables, tokens):
        # monkey-level: run the model but with pos rows shifted. The model
        # reads pos_emb[pos_offset : pos_offset+T]; pos_offset is static 0,
        # so we pre-rotate the table so row 0 is this shard's first position.
        pos = variables["params"]["pos_emb"]
        rolled = jnp.roll(pos, -offset, axis=0)
        new_vars = {
            "params": {**variables["params"], "pos_emb": rolled}
        }
        return model.apply(new_vars, tokens)

    return apply_fn(params, tokens_shard)


class TestKVCacheDecode:
    """Autoregressive decode path: the cached single-token steps must
    reproduce the full-sequence forward exactly (same weights, same
    positions), for both position encodings and under GQA."""

    @pytest.mark.parametrize("pos_encoding", ["learned", "rope"])
    @pytest.mark.parametrize("kv_heads", [None, 2])
    def test_decode_matches_full_forward(self, pos_encoding, kv_heads):
        from chainermn_tpu.models.transformer import init_cache

        model = tiny_lm(pos_encoding=pos_encoding, num_kv_heads=kv_heads)
        B, T = 2, 10
        toks = jax.random.randint(jax.random.PRNGKey(0), (B, T), 1, VOCAB)
        params = model.init(jax.random.PRNGKey(1), toks, train=False)

        full = model.apply(params, toks, train=False)  # [B, T, V]

        cache = init_cache(model, params, B)["cache"]
        got = []
        for t in range(T):
            logits, mut = model.apply(
                {**params, "cache": cache}, toks[:, t:t + 1],
                positions=jnp.full((1,), t, jnp.int32),
                train=False, decode=True, mutable=["cache"],
            )
            cache = mut["cache"]
            got.append(logits[:, 0])
        got = jnp.stack(got, axis=1)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(full), rtol=2e-4, atol=2e-4
        )

    def test_generate_greedy_matches_manual_rollout(self):
        from chainermn_tpu.models.transformer import generate

        model = tiny_lm()
        B, P, N = 2, 5, 12
        prompt = jax.random.randint(jax.random.PRNGKey(2), (B, P), 1, VOCAB)
        params = model.init(jax.random.PRNGKey(3), prompt, train=False)

        out = generate(model, params, prompt, N)
        assert out.shape == (B, N)
        np.testing.assert_array_equal(np.asarray(out[:, :P]),
                                      np.asarray(prompt))

        # Manual greedy rollout via repeated FULL forwards.
        seq = prompt
        for _ in range(N - P):
            logits = model.apply(params, seq, train=False)
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(seq.dtype)
            seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))

    def test_generate_ragged_prompts(self):
        """Right-padded ragged prompts: each row switches to model
        continuations at its own length; prompt tokens pass through."""
        from chainermn_tpu.models.transformer import generate

        model = tiny_lm()
        B, P, N = 2, 6, 9
        rng = jax.random.PRNGKey(4)
        prompt = jax.random.randint(rng, (B, P), 1, VOCAB)
        prompt = prompt.at[1, 3:].set(0)  # row 1 has true length 3
        params = model.init(jax.random.PRNGKey(5), prompt, train=False)

        out = generate(model, params, prompt, N, pad_id=0)
        np.testing.assert_array_equal(np.asarray(out[0, :P]),
                                      np.asarray(prompt[0]))
        np.testing.assert_array_equal(np.asarray(out[1, :3]),
                                      np.asarray(prompt[1, :3]))
        # Row 1's continuation must match a manual rollout from its
        # 3-token prompt alone.
        seq = prompt[1:2, :3]
        for _ in range(N - 3):
            logits = model.apply(params, seq, train=False)
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(seq.dtype)
            seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(np.asarray(out[1]), np.asarray(seq[0]))

    def test_generate_sampling_reproducible_and_capacity_checked(self):
        from chainermn_tpu.models.transformer import generate

        model = tiny_lm()
        B, P = 1, 4
        prompt = jax.random.randint(jax.random.PRNGKey(6), (B, P), 1, VOCAB)
        params = model.init(jax.random.PRNGKey(7), prompt, train=False)
        key = jax.random.PRNGKey(8)
        a = generate(model, params, prompt, 8, temperature=0.7, rng=key)
        b = generate(model, params, prompt, 8, temperature=0.7, rng=key)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        with pytest.raises(ValueError, match="requires rng"):
            generate(model, params, prompt, 8, temperature=0.7)
        with pytest.raises(ValueError, match="cache capacity"):
            generate(model, params, prompt, model.max_len + 1)


class TestSeq2Seq:
    def _batch(self, B=4, Ts=12, Tt=10):
        k = jax.random.PRNGKey(0)
        ks = jax.random.split(k, 4)
        src = jax.random.randint(ks[0], (B, Ts), 1, VOCAB)
        tgt_in = jax.random.randint(ks[1], (B, Tt), 1, VOCAB)
        tgt_out = jax.random.randint(ks[2], (B, Tt), 1, VOCAB)
        src_mask = jnp.ones((B, Ts))
        tgt_mask = jnp.ones((B, Tt))
        return src, tgt_in, tgt_out, src_mask, tgt_mask

    def test_shapes_and_loss(self):
        model = Seq2Seq(src_vocab=VOCAB, tgt_vocab=VOCAB, embed=16, hidden=32)
        src, tgt_in, tgt_out, sm, tm = self._batch()
        params = model.init(jax.random.PRNGKey(1), src, tgt_in, sm, tm)
        logits = model.apply(params, src, tgt_in, sm, tm)
        assert logits.shape == (4, 10, VOCAB)
        assert np.isfinite(float(seq2seq_loss(logits, tgt_out, tm)))

    def test_padding_is_inert(self):
        """Extending sequences with padded steps must not change the logits
        at real positions — the mask-freezing recurrence contract."""
        model = Seq2Seq(src_vocab=VOCAB, tgt_vocab=VOCAB, embed=16, hidden=32)
        src, tgt_in, tgt_out, sm, tm = self._batch(B=2, Ts=8, Tt=6)
        params = model.init(jax.random.PRNGKey(1), src, tgt_in, sm, tm)
        base = model.apply(params, src, tgt_in, sm, tm)

        pad = lambda x, n: jnp.pad(x, ((0, 0), (0, n)))
        src_p, sm_p = pad(src, 4), pad(sm, 4)
        tgt_p, tm_p = pad(tgt_in, 3), pad(tm, 3)
        ext = model.apply(params, src_p, tgt_p, sm_p, tm_p)
        np.testing.assert_allclose(
            np.asarray(ext[:, :6]), np.asarray(base), rtol=1e-5, atol=1e-5
        )

    def test_training_reduces_loss(self):
        model = Seq2Seq(src_vocab=VOCAB, tgt_vocab=VOCAB, embed=16, hidden=32)
        src, tgt_in, tgt_out, sm, tm = self._batch()
        params = model.init(jax.random.PRNGKey(1), src, tgt_in, sm, tm)
        opt = optax.adam(1e-2)
        opt_state = opt.init(params)

        @jax.jit
        def step(params, opt_state):
            def loss_fn(p):
                logits = model.apply(p, src, tgt_in, sm, tm)
                return seq2seq_loss(logits, tgt_out, tm)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        params2, opt_state, l0 = step(params, opt_state)
        for _ in range(10):
            params2, opt_state, ln = step(params2, opt_state)
        assert float(ln) < float(l0)


class TestBucketing:
    def test_bucket_length(self):
        assert bucket_length(1) == 16
        assert bucket_length(16) == 16
        assert bucket_length(17) == 32
        assert bucket_length(10_000) == DEFAULT_BUCKETS[-1]

    def test_batches_fixed_shapes(self):
        rng = np.random.RandomState(0)
        pairs = [
            (
                list(rng.randint(1, 50, size=rng.randint(3, 40))),
                list(rng.randint(1, 50, size=rng.randint(3, 40))),
            )
            for _ in range(100)
        ]
        shapes = set()
        n_items = 0
        for batch in bucket_batches(pairs, 8, drop_remainder=False):
            assert batch["src"].shape == batch["tgt"].shape
            assert batch["src"].shape[0] == 8
            shapes.add(batch["src"].shape[1])
            n_items += 8
            # mask marks real tokens only
            assert batch["src_mask"].sum() <= batch["src"].size
        assert shapes <= set(DEFAULT_BUCKETS)
        assert n_items >= 100  # remainder batches pad up, never drop


class TestWindowedDecode:
    """Model-level sliding window: training (windowed flash) and KV-cache
    decode must see the SAME attention band."""

    def _windowed_model(self, window):
        return windowed_lm(window)

    def test_windowed_decode_matches_windowed_forward(self):
        from chainermn_tpu.models.transformer import init_cache

        window = 4
        model = self._windowed_model(window)
        B, T = 2, 12
        toks = jax.random.randint(jax.random.PRNGKey(20), (B, T), 1, VOCAB)
        params = model.init(jax.random.PRNGKey(21), toks, train=False)
        full = model.apply(params, toks, train=False)

        cache = init_cache(model, params, B)["cache"]
        got = []
        for t in range(T):
            logits, mut = model.apply(
                {**params, "cache": cache}, toks[:, t:t + 1],
                positions=jnp.full((1,), t, jnp.int32),
                train=False, decode=True, mutable=["cache"],
            )
            cache = mut["cache"]
            got.append(logits[:, 0])
        np.testing.assert_allclose(
            np.asarray(jnp.stack(got, axis=1)), np.asarray(full),
            rtol=2e-4, atol=2e-4,
        )

    def test_window_without_attention_fn_rejected(self):
        model = tiny_lm(window=4)
        toks = jnp.ones((1, 8), jnp.int32)
        with pytest.raises(ValueError, match="window-honouring"):
            model.init(jax.random.PRNGKey(0), toks, train=False)


class TestBeamSearch:
    def test_beam1_equals_greedy(self):
        from chainermn_tpu.models.transformer import beam_search, generate

        model = tiny_lm()
        B, P, N = 2, 4, 10
        prompt = jax.random.randint(jax.random.PRNGKey(40), (B, P), 1, VOCAB)
        params = model.init(jax.random.PRNGKey(41), prompt, train=False)
        greedy = generate(model, params, prompt, N)
        beams, scores = beam_search(model, params, prompt, N, beam_size=1)
        np.testing.assert_array_equal(np.asarray(beams[:, 0]),
                                      np.asarray(greedy))
        assert np.all(np.isfinite(np.asarray(scores)))

    def test_scores_are_true_log_probs_and_ordered(self):
        """Each returned hypothesis's score must equal the sum of its own
        next-token log-probs under a full forward — and the top beam must
        score at least as high as greedy."""
        from chainermn_tpu.models.transformer import beam_search, generate

        model = tiny_lm()
        B, P, N, K = 1, 3, 8, 3
        prompt = jax.random.randint(jax.random.PRNGKey(42), (B, P), 1, VOCAB)
        params = model.init(jax.random.PRNGKey(43), prompt, train=False)
        beams, scores = beam_search(model, params, prompt, N, beam_size=K)

        def seq_logprob(seq):
            logits = model.apply(params, seq[None], train=False)[0]
            lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            # generated positions: P..N-1; token at t scored by logits at t-1
            idx = jnp.arange(P, N)
            return float(jnp.sum(lp[idx - 1, seq[idx]]))

        for k in range(K):
            expected = seq_logprob(beams[0, k])
            np.testing.assert_allclose(float(scores[0, k]), expected,
                                       rtol=1e-4, atol=1e-4)
        assert np.all(np.diff(np.asarray(scores[0])) <= 1e-6)  # sorted

        greedy = generate(model, params, prompt, N)
        assert float(scores[0, 0]) >= seq_logprob(greedy[0]) - 1e-5

    def test_eos_freezes_beam(self):
        """Designate the model's own argmax continuation as EOS so the
        top beam is GUARANTEED to emit it at the first free position —
        the frozen beam must then pad out at an unchanged score. (An
        arbitrary eos id would make every assertion vacuously skippable
        when it never lands in a beam.)"""
        from chainermn_tpu.models.transformer import beam_search, generate

        model = tiny_lm()
        B, P, N, K = 1, 2, 7, 2
        prompt = jnp.asarray([[7, 9]], jnp.int32)
        params = model.init(jax.random.PRNGKey(44), prompt, train=False)
        greedy = generate(model, params, prompt, N)
        eos = int(greedy[0, P])  # the argmax first continuation
        assert eos != 0  # pad would confuse the check

        beams, scores = beam_search(model, params, prompt, N, beam_size=K,
                                    eos_id=eos)
        beams = np.asarray(beams)
        # Some hypothesis must contain the designated EOS.
        assert np.any(beams == eos)
        hit = False
        for k in range(K):
            row = beams[0, k]
            eos_pos = np.where(row == eos)[0]
            if eos_pos.size:
                hit = True
                assert np.all(row[eos_pos[0] + 1:] == 0)
        assert hit
        # The frozen hypothesis [prompt, eos, pad...] scores exactly the
        # eos token's log-prob — verify against a full forward.
        frozen = np.asarray([[*np.asarray(prompt[0]), eos] + [0] * (N - P - 1)])
        k_frozen = next(
            k for k in range(K)
            if np.array_equal(beams[0, k], frozen[0])
        )
        logits = model.apply(params, jnp.asarray(frozen), train=False)[0]
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        np.testing.assert_allclose(
            float(scores[0, k_frozen]), float(lp[P - 1, eos]),
            rtol=1e-4, atol=1e-4,
        )

    def test_capacity_and_beam_validation(self):
        from chainermn_tpu.models.transformer import beam_search

        model = tiny_lm()
        prompt = jnp.ones((1, 3), jnp.int32)
        params = model.init(jax.random.PRNGKey(45), prompt, train=False)
        with pytest.raises(ValueError, match="cache capacity"):
            beam_search(model, params, prompt, model.max_len + 1, 2)
        with pytest.raises(ValueError, match="beam_size"):
            beam_search(model, params, prompt, 6, 0)


class TestSeq2SeqBeam:
    def _setup(self):
        from chainermn_tpu.models import Seq2Seq

        model = Seq2Seq(src_vocab=VOCAB, tgt_vocab=VOCAB, embed=16,
                        hidden=32, num_layers=2)
        B, Ts = 2, 6
        src = jax.random.randint(jax.random.PRNGKey(50), (B, Ts), 3, VOCAB)
        mask = jnp.ones((B, Ts))
        variables = model.init(jax.random.PRNGKey(51), src,
                               src[:, :4], mask, jnp.ones((B, 4)))
        return model, variables, src, mask

    def test_beam1_equals_greedy(self):
        from chainermn_tpu.models.seq2seq import (
            beam_search_decode,
            greedy_decode,
        )

        model, variables, src, mask = self._setup()
        N = 8
        g = greedy_decode(model, variables, src, mask, N)
        beams, scores = beam_search_decode(model, variables, src, mask, N,
                                           beam_size=1)
        np.testing.assert_array_equal(np.asarray(beams[:, 0]), np.asarray(g))
        assert np.all(np.isfinite(np.asarray(scores)))

    def test_scores_are_true_log_probs(self):
        """Each hypothesis's score equals the teacher-forced log-prob of
        its tokens up to and including the first EOS (frozen steps add
        exactly zero)."""
        from chainermn_tpu.models.seq2seq import beam_search_decode

        model, variables, src, mask = self._setup()
        N, K = 7, 3
        bos, eos = 1, 2
        beams, scores = beam_search_decode(model, variables, src, mask, N,
                                           beam_size=K, bos=bos, eos=eos)
        beams_np = np.asarray(beams)
        for b in range(src.shape[0]):
            for k in range(K):
                hyp = beams_np[b, k]
                dec_in = jnp.asarray(
                    np.concatenate([[bos], hyp[:-1]])[None]
                )
                logits = model.apply(
                    variables, src[b:b + 1], dec_in, mask[b:b + 1],
                    jnp.ones((1, N)),
                )[0]
                lp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
                eos_pos = np.where(hyp == eos)[0]
                upto = (eos_pos[0] + 1) if eos_pos.size else N
                expected = float(sum(
                    lp[t, hyp[t]] for t in range(upto)
                ))
                np.testing.assert_allclose(float(scores[b, k]), expected,
                                           rtol=1e-4, atol=1e-4)
        # best-first ordering
        assert np.all(np.diff(np.asarray(scores), axis=1) <= 1e-6)

    def test_top_beam_at_least_greedy(self):
        from chainermn_tpu.models.seq2seq import (
            beam_search_decode,
            greedy_decode,
        )

        model, variables, src, mask = self._setup()
        N = 8
        beams, scores = beam_search_decode(model, variables, src, mask, N,
                                           beam_size=4)
        g = greedy_decode(model, variables, src, mask, N)
        # score the greedy hypothesis the same way
        bos, eos = 1, 2
        g_np = np.asarray(g)
        for b in range(src.shape[0]):
            dec_in = jnp.asarray(np.concatenate([[bos], g_np[b, :-1]])[None])
            logits = model.apply(
                variables, src[b:b + 1], dec_in, mask[b:b + 1],
                jnp.ones((1, N)),
            )[0]
            lp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            eos_pos = np.where(g_np[b] == eos)[0]
            upto = (eos_pos[0] + 1) if eos_pos.size else N
            g_score = float(sum(lp[t, g_np[b, t]] for t in range(upto)))
            assert float(scores[b, 0]) >= g_score - 1e-5


class TestLengthPenalty:
    def test_alpha0_is_identity_transformer(self):
        from chainermn_tpu.models.transformer import beam_search

        model = tiny_lm()
        prompt = jax.random.randint(jax.random.PRNGKey(60), (2, 3), 1, VOCAB)
        params = model.init(jax.random.PRNGKey(61), prompt, train=False)
        a = beam_search(model, params, prompt, 9, 3)
        b = beam_search(model, params, prompt, 9, 3, length_penalty=0.0)
        np.testing.assert_array_equal(np.asarray(a[0]), np.asarray(b[0]))
        np.testing.assert_array_equal(np.asarray(a[1]), np.asarray(b[1]))

    def test_penalized_ranking_is_monotone(self):
        """With alpha > 0 the returned order must sort the PENALIZED
        scores descending (recomputed from the returned hypotheses'
        generated lengths), while raw scores come back unpenalized."""
        from chainermn_tpu.models.transformer import beam_search, generate

        model = tiny_lm()
        B, P, N, K = 1, 3, 9, 3
        prompt = jax.random.randint(jax.random.PRNGKey(62), (B, P), 1, VOCAB)
        params = model.init(jax.random.PRNGKey(63), prompt, train=False)
        # designate the argmax continuation as EOS so lengths VARY
        eos = int(generate(model, params, prompt, N)[0, P])
        alpha = 5.0
        beams, scores = beam_search(model, params, prompt, N, K,
                                    eos_id=eos, length_penalty=alpha)
        beams_np, pen = np.asarray(beams), []
        for k in range(K):
            row = beams_np[0, k, P:]
            eos_pos = np.where(row == eos)[0]
            glen = (eos_pos[0] + 1) if eos_pos.size else N - P
            pen.append(float(scores[0, k]) / ((5.0 + glen) / 6.0) ** alpha)
        assert all(pen[i] >= pen[i + 1] - 1e-5 for i in range(K - 1)), pen

    def test_alpha0_is_identity_seq2seq(self):
        from chainermn_tpu.models.seq2seq import beam_search_decode

        from chainermn_tpu.models import Seq2Seq

        model = Seq2Seq(src_vocab=VOCAB, tgt_vocab=VOCAB, embed=16,
                        hidden=32, num_layers=1)
        src = jax.random.randint(jax.random.PRNGKey(64), (2, 5), 3, VOCAB)
        mask = jnp.ones((2, 5))
        variables = model.init(jax.random.PRNGKey(65), src, src[:, :3],
                               mask, jnp.ones((2, 3)))
        a = beam_search_decode(model, variables, src, mask, 7, 3)
        b = beam_search_decode(model, variables, src, mask, 7, 3,
                               length_penalty=0.0)
        np.testing.assert_array_equal(np.asarray(a[0]), np.asarray(b[0]))

    def test_penalized_ranking_is_monotone_seq2seq(self):
        from chainermn_tpu.models import Seq2Seq
        from chainermn_tpu.models.seq2seq import beam_search_decode

        model = Seq2Seq(src_vocab=VOCAB, tgt_vocab=VOCAB, embed=16,
                        hidden=32, num_layers=1)
        src = jax.random.randint(jax.random.PRNGKey(66), (1, 5), 3, VOCAB)
        mask = jnp.ones((1, 5))
        variables = model.init(jax.random.PRNGKey(67), src, src[:, :3],
                               mask, jnp.ones((1, 3)))
        N, K, alpha, eos = 8, 4, 5.0, 2
        beams, scores = beam_search_decode(
            model, variables, src, mask, N, K, eos=eos,
            length_penalty=alpha,
        )
        beams_np, pen = np.asarray(beams), []
        for k in range(K):
            row = beams_np[0, k]
            eos_pos = np.where(row == eos)[0]
            glen = (eos_pos[0] + 1) if eos_pos.size else N
            pen.append(float(scores[0, k]) / ((5.0 + glen) / 6.0) ** alpha)
        assert all(pen[i] >= pen[i + 1] - 1e-5 for i in range(K - 1)), pen


class TestDropout:
    def test_dropout_active_in_train_inert_in_eval(self):
        model = tiny_lm(dropout_rate=0.5)
        tokens = jax.random.randint(jax.random.PRNGKey(70), (2, 12), 1, VOCAB)
        params = model.init(
            {"params": jax.random.PRNGKey(71),
             "dropout": jax.random.PRNGKey(72)},
            tokens,
        )
        # train=True: different dropout rngs -> different logits
        a = model.apply(params, tokens, train=True,
                        rngs={"dropout": jax.random.PRNGKey(1)})
        b = model.apply(params, tokens, train=True,
                        rngs={"dropout": jax.random.PRNGKey(2)})
        assert not np.allclose(np.asarray(a), np.asarray(b), atol=1e-4)
        # eval: no rng needed, deterministic, equals the rate-0 model
        e1 = model.apply(params, tokens, train=False)
        e2 = model.apply(params, tokens, train=False)
        np.testing.assert_array_equal(np.asarray(e1), np.asarray(e2))
        ref = tiny_lm().apply(params, tokens, train=False)
        np.testing.assert_allclose(np.asarray(e1), np.asarray(ref),
                                   rtol=1e-6, atol=1e-6)

    def test_dropout_composes_with_remat(self):
        model = tiny_lm(dropout_rate=0.3, remat=True)
        tokens = jax.random.randint(jax.random.PRNGKey(73), (2, 8), 1, VOCAB)
        params = model.init(
            {"params": jax.random.PRNGKey(74),
             "dropout": jax.random.PRNGKey(75)},
            tokens,
        )

        def loss(p):
            logits = model.apply(
                p, tokens, train=True,
                rngs={"dropout": jax.random.PRNGKey(3)},
            )
            return lm_loss(logits, tokens)

        g = jax.grad(loss)(params)
        assert all(np.isfinite(np.asarray(x)).all()
                   for x in jax.tree.leaves(g))


class TestSamplingFilters:
    def test_filter_logits_top_k(self):
        from chainermn_tpu.models.transformer import _filter_logits

        logits = jnp.asarray([[1.0, 3.0, 2.0, 0.5, -1.0]])
        out = np.asarray(_filter_logits(logits, 2, None))
        assert np.isfinite(out[0, 1]) and np.isfinite(out[0, 2])
        assert np.isneginf(out[0, 0]) and np.isneginf(out[0, 3])
        assert np.isneginf(out[0, 4])

    def test_filter_logits_top_p(self):
        from chainermn_tpu.models.transformer import _filter_logits

        # probs ~ [0.643, 0.237, 0.087, 0.032] for logits [3, 2, 1, 0]
        logits = jnp.asarray([[3.0, 2.0, 1.0, 0.0]])
        # top_p=0.7: mass before token0=0 < .7 keep; before token1=.643<.7
        # keep; before token2=.880>.7 drop.
        out = np.asarray(_filter_logits(logits, None, 0.7))
        assert np.isfinite(out[0, 0]) and np.isfinite(out[0, 1])
        assert np.isneginf(out[0, 2]) and np.isneginf(out[0, 3])
        # top_p tiny: only the argmax survives
        out1 = np.asarray(_filter_logits(logits, None, 1e-6))
        assert np.isfinite(out1[0, 0]) and np.all(np.isneginf(out1[0, 1:]))
        # top_p=1.0 keeps everything
        outall = np.asarray(_filter_logits(logits, None, 1.0))
        assert np.all(np.isfinite(outall))

    def test_temperature_applies_before_nucleus(self):
        """Round-4 ADVICE: the nucleus must be selected from the
        temperature-adjusted distribution (HF order). A hot temperature
        flattens the distribution, so MORE tokens survive a fixed top_p;
        under the wrong (filter-then-temperature) order the survivor set
        would be temperature-independent."""
        from chainermn_tpu.models.transformer import _tempered_filtered

        logits = jnp.asarray([[3.0, 2.0, 1.0, 0.0]])
        cold = np.asarray(_tempered_filtered(logits, 1.0, None, 0.7))
        hot = np.asarray(_tempered_filtered(logits, 4.0, None, 0.7))
        assert np.isfinite(cold).sum() == 2  # probs .64/.24: keep 2
        assert np.isfinite(hot).sum() == 3   # flattened: keep 3

    def test_prompt_len_is_prefix_before_first_pad(self):
        """Round-4 ADVICE: a vocabulary token EQUAL to pad_id mid-prompt
        must not inflate the teacher-forcing length — the true length is
        the index of the FIRST pad."""
        from chainermn_tpu.models.transformer import _decode_setup

        model = tiny_lm()
        prompt = jnp.asarray([
            [5, 0, 7, 0],   # first pad at 1 (7 is unreachable junk)
            [5, 3, 7, 2],   # no pad: full length 4
            [5, 3, 0, 0],   # ordinary right-padding: 2
        ], jnp.int32)
        _, _, plen, _ = _decode_setup(model, None, prompt, 6, 0)
        np.testing.assert_array_equal(np.asarray(plen), [1, 4, 2])

    def test_generate_with_filters_runs_and_validates(self):
        from chainermn_tpu.models.transformer import generate

        model = tiny_lm()
        prompt = jax.random.randint(jax.random.PRNGKey(80), (1, 4), 1, VOCAB)
        params = model.init(jax.random.PRNGKey(81), prompt, train=False)
        key = jax.random.PRNGKey(82)
        out = generate(model, params, prompt, 9, temperature=0.8,
                       top_k=5, top_p=0.9, rng=key)
        assert out.shape == (1, 9)
        # top_k=1 sampling == greedy regardless of temperature
        g = generate(model, params, prompt, 9)
        s1 = generate(model, params, prompt, 9, temperature=2.0, top_k=1,
                      rng=key)
        np.testing.assert_array_equal(np.asarray(s1), np.asarray(g))
        with pytest.raises(ValueError, match="temperature > 0"):
            generate(model, params, prompt, 9, top_k=3)
        with pytest.raises(ValueError, match="top_p must be"):
            generate(model, params, prompt, 9, temperature=1.0, top_p=1.5,
                     rng=key)

    def test_top_k_range_validated(self):
        from chainermn_tpu.models.transformer import generate

        model = tiny_lm()
        prompt = jnp.ones((1, 3), jnp.int32)
        params = model.init(jax.random.PRNGKey(83), prompt, train=False)
        key = jax.random.PRNGKey(84)
        with pytest.raises(ValueError, match="top_k must be"):
            generate(model, params, prompt, 6, temperature=1.0, top_k=0,
                     rng=key)
        with pytest.raises(ValueError, match="top_k must be"):
            generate(model, params, prompt, 6, temperature=1.0,
                     top_k=VOCAB + 1, rng=key)


class TestFilterLogitsEdges:
    """ISSUE 4 satellite: ``_filter_logits`` is now shared by
    ``generate`` AND the serving engine's sampling tail — its edges are
    pinned against a literal numpy reference (HF semantics: top_k first,
    the nucleus renormalized AFTER top_k; ties at the k-th/threshold
    logit survive, matching the strict ``<`` masking)."""

    @staticmethod
    def _np_reference(logits, top_k, top_p):
        out = np.array(logits, np.float32)
        V = out.shape[-1]
        for b in range(out.shape[0]):
            row = np.array(logits[b], np.float64)
            keep = np.ones(V, bool)
            if top_k is not None:
                kth = np.sort(row)[::-1][top_k - 1]
                keep &= row >= kth
            if top_p is not None:
                r = np.sort(row)[::-1]
                if top_k is not None:
                    r[top_k:] = -np.inf
                e = np.exp(r - np.max(r))
                cum = np.cumsum(e / e.sum())
                keep_sorted = np.concatenate(([True], cum[:-1] < top_p))
                thresh = np.min(r[keep_sorted])
                keep &= row >= thresh
            out[b, ~keep] = -np.inf
        return out

    @pytest.mark.parametrize("top_k,top_p", [
        (1, None),           # greedy-degenerate k
        (VOCAB, None),       # k == vocab: no-op
        (None, 1.0),         # full nucleus: no-op
        (3, 0.7),            # combined: nucleus within the k survivors
        (1, 0.5),            # combined degenerate
        (VOCAB, 0.9),        # k no-op, p active
        (4, 1.0),            # p no-op, k active
        (None, 0.3),
    ])
    def test_matches_numpy_reference(self, top_k, top_p):
        from chainermn_tpu.models.transformer import _filter_logits

        rng = np.random.RandomState(0)
        logits = (rng.randn(4, VOCAB) * 2).astype(np.float32)
        got = np.asarray(_filter_logits(jnp.asarray(logits), top_k, top_p))
        want = self._np_reference(logits, top_k, top_p)
        np.testing.assert_array_equal(np.isneginf(got), np.isneginf(want))
        # surviving logits pass through untouched
        m = np.isfinite(want)
        np.testing.assert_array_equal(got[m], logits[m])

    def test_top_k_1_keeps_exactly_the_argmax(self):
        from chainermn_tpu.models.transformer import _filter_logits

        rng = np.random.RandomState(1)
        logits = (rng.randn(5, VOCAB)).astype(np.float32)
        got = np.asarray(_filter_logits(jnp.asarray(logits), 1, None))
        assert (np.isfinite(got).sum(axis=-1) == 1).all()
        np.testing.assert_array_equal(np.argmax(got, -1),
                                      np.argmax(logits, -1))

    def test_top_k_vocab_and_top_p_1_are_no_ops(self):
        from chainermn_tpu.models.transformer import _filter_logits

        rng = np.random.RandomState(2)
        logits = (rng.randn(3, VOCAB)).astype(np.float32)
        for k, p in ((VOCAB, None), (None, 1.0), (VOCAB, 1.0)):
            np.testing.assert_array_equal(
                np.asarray(_filter_logits(jnp.asarray(logits), k, p)),
                logits,
            )

    def test_top_p_0_keeps_one_token_never_an_empty_set(self):
        """generate() rejects top_p=0 at the API, but the filter itself
        must stay total: the first sorted token is ALWAYS kept, so a
        zero-mass nucleus degrades to the argmax, not to a row of
        -inf that categorical() would turn into NaN."""
        from chainermn_tpu.models.transformer import _filter_logits

        rng = np.random.RandomState(3)
        logits = (rng.randn(4, VOCAB)).astype(np.float32)
        got = np.asarray(_filter_logits(jnp.asarray(logits), None, 0.0))
        assert (np.isfinite(got).sum(axis=-1) == 1).all()
        np.testing.assert_array_equal(np.argmax(got, -1),
                                      np.argmax(logits, -1))


class TestWindowedBeam:
    def test_beam1_on_windowed_model_equals_windowed_greedy(self):
        """Beam decoding shares _decode_attend, so the window band must
        apply identically: K=1 beam == greedy on a windowed model, and
        both reflect the banded distribution (scores equal the windowed
        full forward's log-probs)."""
        from chainermn_tpu.models.transformer import beam_search, generate

        model = windowed_lm(4)
        B, P, N = 1, 3, 9
        prompt = jax.random.randint(jax.random.PRNGKey(90), (B, P), 1, VOCAB)
        params = model.init(jax.random.PRNGKey(91), prompt, train=False)
        g = generate(model, params, prompt, N)
        beams, scores = beam_search(model, params, prompt, N, beam_size=1)
        np.testing.assert_array_equal(np.asarray(beams[:, 0]), np.asarray(g))
        # score == sum of the WINDOWED model's log-probs for the sequence
        logits = model.apply(params, beams[0, 0][None], train=False)[0]
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        idx = jnp.arange(P, N)
        expected = float(jnp.sum(lp[idx - 1, beams[0, 0][idx]]))
        np.testing.assert_allclose(float(scores[0, 0]), expected,
                                   rtol=1e-4, atol=1e-4)


class TestBidirectionalEncoder:
    """TransformerLM with causal=False: the BERT/MLM-style text encoder
    (round 5, beyond the reference) on the same weight-tied module."""

    def _tiny(self, causal):
        from chainermn_tpu.models import TransformerLM

        return TransformerLM(
            vocab_size=32, num_layers=2, d_model=32, num_heads=2,
            d_ff=64, max_len=16, compute_dtype=jnp.float32,
            causal=causal,
        )

    def test_future_token_dependency_is_the_causal_flag(self):
        """Position 0's logits must see token 5 iff causal=False — the
        defining behavioural difference, pinned directly."""
        import numpy as np

        toks = jnp.arange(8)[None] % 32
        toks2 = toks.at[0, 5].set((toks[0, 5] + 7) % 32)
        for causal, changes in ((False, True), (True, False)):
            m = self._tiny(causal)
            p = m.init(jax.random.PRNGKey(0), toks, train=False)
            a = m.apply(p, toks, train=False)[0, 0]
            b = m.apply(p, toks2, train=False)[0, 0]
            changed = bool(jnp.any(jnp.abs(a - b) > 1e-6))
            assert changed == changes, (causal, changed)

    def test_decode_rejected_when_bidirectional(self):
        import pytest

        m = self._tiny(False)
        toks = jnp.zeros((1, 8), jnp.int32)
        p = m.init(jax.random.PRNGKey(0), toks, train=False)
        with pytest.raises(ValueError, match="causal=True"):
            m.apply(p, jnp.zeros((1, 1), jnp.int32), train=False,
                    decode=True, mutable=["cache"])

    def test_mlm_trains_to_recover_masked_tokens(self):
        """End-to-end MLM drill on a COPY task: every row carries one
        random token (resampled each step — nothing to memorise), so a
        masked position is inferable from ANY other position. A
        bidirectional encoder drives masked loss to ~zero; a causal one
        irreducibly fails whenever the masked position has no unmasked
        LEFT context (position 0 masked ≈ a third of rows at rate 0.3)
        — the contrast moves if the causality plumbing regresses in
        either direction."""
        import optax

        from chainermn_tpu.models import mlm_corrupt, mlm_loss

        V, MASK_ID, V_REAL, B, T = 32, 31, 16, 16, 8

        def batch_of(rng):
            c = jax.random.randint(rng, (B, 1), 0, V_REAL)
            return jnp.tile(c, (1, T))

        def train(causal, steps=300):
            m = self._tiny(causal)
            p = m.init(jax.random.PRNGKey(0),
                       batch_of(jax.random.PRNGKey(1)), train=False)
            opt = optax.adam(3e-3)
            s = opt.init(p)

            @jax.jit
            def step(p, s, rng):
                kb, kc = jax.random.split(rng)
                toks = batch_of(kb)
                x, sel = mlm_corrupt(
                    kc, toks, mask_id=MASK_ID, vocab_size=V, rate=0.3
                )

                def loss_fn(p):
                    return mlm_loss(
                        m.apply(p, x, train=False), toks, sel
                    )

                loss, g = jax.value_and_grad(loss_fn)(p)
                u, s2 = opt.update(g, s, p)
                return optax.apply_updates(p, u), s2, loss

            rng = jax.random.PRNGKey(7)
            for i in range(steps):
                rng, k = jax.random.split(rng)
                p, s, _ = step(p, s, k)
            # Deterministic eval: fixed batch + fixed mask draw.
            toks = batch_of(jax.random.PRNGKey(98))
            x, sel = mlm_corrupt(
                jax.random.PRNGKey(99), toks, mask_id=MASK_ID,
                vocab_size=V, rate=0.3,
            )
            return float(mlm_loss(m.apply(p, x, train=False), toks, sel))

        final = train(causal=False)
        assert final < 0.15, final
        assert train(causal=True) > 3 * final
