"""Odd-gradient-shape stress tests — the role of BASELINE.json's
"Faster-RCNN (stress hierarchical communicator, odd grad shapes)" config
and the reference's mixed-dtype/empty-grad communicator tests
(``tests/communicator_tests/test_communicator.py`` (dagger), SURVEY.md
section 4): gradient reduction and the ZeRO scatter must survive scalars,
odd prime dims, empty leaves and mixed dtypes."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from chainermn_tpu.optimizers import allreduce_gradients


def _odd_tree():
    k = jax.random.PRNGKey(0)
    ks = jax.random.split(k, 4)
    return {
        "scalar": jnp.float32(3.5),
        "vec1": jnp.ones((1,)),
        "prime": jax.random.normal(ks[0], (3, 5, 7)),
        "empty": jnp.zeros((0, 4)),
        "big_odd": jax.random.normal(ks[1], (127, 33)),
        "bf16": jax.random.normal(ks[2], (11, 13)).astype(jnp.bfloat16),
        "int_buffer": jnp.arange(7, dtype=jnp.int32),  # non-float leaf
    }


@pytest.mark.parametrize("compress", [None, jnp.bfloat16])
def test_allreduce_grad_odd_shapes(comm, compress):
    tree = _odd_tree()
    ax = comm.axis_name

    def local(tree):
        return allreduce_gradients(tree, comm, compress_dtype=compress)

    out = jax.jit(
        shard_map(
            local, mesh=comm.mesh, in_specs=P(), out_specs=P(),
            check_vma=False,
        )
    )(tree)
    # Identical input on every shard => pmean is identity (up to cast).
    for name in tree:
        assert out[name].dtype == tree[name].dtype, name
        assert out[name].shape == tree[name].shape, name
        tol = 1e-2 if (compress or tree[name].dtype == jnp.bfloat16) else 1e-6
        if tree[name].size:
            np.testing.assert_allclose(
                np.asarray(out[name], np.float64),
                np.asarray(tree[name], np.float64),
                rtol=tol, atol=tol,
            )


def test_zero_sharding_odd_shapes(comm):
    """ZeRO chunking pads odd sizes; round-trip must preserve values."""
    from chainermn_tpu.parallel.zero import (
        zero_shard_optimizer,
        zero_state_specs,
    )

    params = {
        "scalar": jnp.float32(1.0),
        "prime": jax.random.normal(jax.random.PRNGKey(1), (3, 5, 7)),
        "vec1": jnp.ones((1,)),
    }
    ax = comm.axis_name
    inner = optax.sgd(0.5)
    zopt = zero_shard_optimizer(inner, ax)
    st_spec = zero_state_specs(inner, params, comm.size, ax)

    def local(params):
        state = zopt.init(params)
        grads = jax.tree.map(jnp.ones_like, params)
        updates, _ = zopt.update(grads, state, params)
        return updates

    updates = jax.jit(
        shard_map(
            local, mesh=comm.mesh, in_specs=P(), out_specs=P(),
            check_vma=False,
        )
    )(params)
    # sgd(0.5) on all-ones grads => every update == -0.5 exactly.
    for name, u in updates.items():
        np.testing.assert_allclose(np.asarray(u), -0.5, rtol=1e-6)
        assert u.shape == params[name].shape


def test_train_step_odd_param_shapes(comm):
    """Full train step with a model whose params include odd shapes."""
    from chainermn_tpu.training.train_step import (
        create_train_state,
        make_train_step,
    )

    def apply(params, x):
        return x @ params["w"] + params["b"] + params["scale"]

    params = {
        "w": jax.random.normal(jax.random.PRNGKey(0), (13, 3)),
        "b": jnp.zeros((3,)),
        "scale": jnp.float32(0.0),  # scalar param
    }
    x = jax.random.normal(jax.random.PRNGKey(1), (16, 13))
    y = jax.random.normal(jax.random.PRNGKey(2), (16, 3))

    def loss_fn(params, batch):
        xb, yb = batch
        return ((apply(params, xb) - yb) ** 2).mean()

    opt = optax.sgd(0.1)
    state = create_train_state(params, opt)
    step = make_train_step(loss_fn, opt, comm)
    new_state, metrics = step(state, (x, y))
    assert np.isfinite(float(metrics["loss"]))
    assert new_state.params["scale"].shape == ()