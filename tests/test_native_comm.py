"""Native TCP host-communicator tests: build the C++ library and run real
multi-process collectives on localhost — the reference tested its MPI plane
with ``mpiexec -n 2..4`` (SURVEY.md section 4); this is the same coverage
with OS processes + TCP instead of MPI ranks."""

import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
WORKER = REPO / "tests" / "native_worker.py"


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_native_lib_builds():
    from chainermn_tpu.native import lib_path

    assert lib_path().exists()


@pytest.mark.parametrize("size", [2, 4])
def test_multiprocess_collectives(size):
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)  # keep workers off the axon plugin path
    procs = [
        subprocess.Popen(
            [sys.executable, str(WORKER), str(r), str(size), coord],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd=str(REPO),
        )
        for r in range(size)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=120)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out}"
        assert f"WORKER_OK {r}" in out


# ----------------------------------------------------------------------
# TcpGroupComm units (ISSUE 8 satellite): the router's health checks
# lean on split()/probe() — pin nested rank translation and probe
# boundedness WITHOUT sockets, against a scripted parent (the real
# multi-process forms run in native_worker.py above).
# ----------------------------------------------------------------------

from collections import deque

from chainermn_tpu.native.tcp_comm import TcpGroupComm


class _ScriptedParent:
    """Single-process stand-in for the p2p plane: records send
    destinations, serves queued receives, probe reads the queue —
    never blocks, so a probe that WOULD hang fails the test instantly
    instead."""

    def __init__(self, rank, size):
        self.rank, self.size = rank, size
        self.sent = []
        self.inbox = {}

    def send_obj(self, obj, dest):
        self.sent.append((dest, obj))

    def recv_obj(self, source):
        q = self.inbox.get(source)
        if not q:
            raise LookupError(f"nothing queued from {source}")
        return q.popleft()

    def probe(self, source):
        return bool(self.inbox.get(source))


def test_group_comm_nested_split_translation():
    """``members`` always refers to the IMMEDIATE parent's rank space
    and translation composes: a nested group's send lands on the right
    WORLD rank after two hops."""
    parent = _ScriptedParent(rank=4, size=6)
    g = TcpGroupComm(parent, [1, 2, 4])
    assert (g.rank, g.size) == (2, 3)
    gg = TcpGroupComm(g, [0, 2])  # g-rank space: world ranks 1 and 4
    assert (gg.rank, gg.size) == (1, 2)
    gg.send_obj("hello", 0)
    assert parent.sent == [(1, "hello")]  # two-level translation
    parent.inbox[1] = deque(["reply"])
    assert gg.probe(0) is True
    assert gg.recv_obj(0) == "reply"
    # three levels deep: a singleton still addresses itself correctly
    ggg = TcpGroupComm(gg, [1])
    assert (ggg.rank, ggg.size) == (0, 1)
    ggg.send_obj("self", 0)
    assert parent.sent[-1] == (4, "self")


def test_group_comm_probe_silent_peer_is_bounded():
    """probe() of a peer that never sends returns False immediately,
    every time — a bounded poll, never a hang (the router's health
    check contract)."""
    import time

    parent = _ScriptedParent(rank=0, size=4)
    g = TcpGroupComm(parent, [0, 2])
    t0 = time.perf_counter()
    for _ in range(100):
        assert g.probe(1) is False
    assert time.perf_counter() - t0 < 1.0
    # a message appearing flips it without consuming
    parent.inbox[2] = deque(["late"])
    assert g.probe(1) is True
    assert g.probe(1) is True  # non-consuming, like MPI_Iprobe
    assert g.recv_obj(1) == "late"
    assert g.probe(1) is False


def test_group_comm_rejects_nonmember_constructor():
    parent = _ScriptedParent(rank=3, size=4)
    with pytest.raises(ValueError, match="not in its own split group"):
        TcpGroupComm(parent, [0, 1])
