"""Native TCP host-communicator tests: build the C++ library and run real
multi-process collectives on localhost — the reference tested its MPI plane
with ``mpiexec -n 2..4`` (SURVEY.md section 4); this is the same coverage
with OS processes + TCP instead of MPI ranks."""

import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
WORKER = REPO / "tests" / "native_worker.py"


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_native_lib_builds():
    from chainermn_tpu.native import lib_path

    assert lib_path().exists()


@pytest.mark.parametrize("size", [2, 4])
def test_multiprocess_collectives(size):
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)  # keep workers off the axon plugin path
    procs = [
        subprocess.Popen(
            [sys.executable, str(WORKER), str(r), str(size), coord],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd=str(REPO),
        )
        for r in range(size)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=120)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out}"
        assert f"WORKER_OK {r}" in out
