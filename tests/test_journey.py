"""Cross-rank request journeys (ISSUE 17).

The load-bearing acceptance pins:

- **Causal chain completeness** — every request routed through a
  2-replica disaggregated cluster reconstructs from the trace to ONE
  complete, contiguous, orphan-free journey, and its TTFT critical-path
  decomposition (queue wait / prefill / handoff / preemption gap) sums
  back to the measured ``ttft_s`` within rounding + clock uncertainty
  (``journey.check_journeys`` — the same predicate dryrun phase Q
  drives).
- **Clock-sync honesty** — the NTP-style two-way estimate recovers a
  simulated skew to within its OWN reported uncertainty, and the merge
  shifts cross-rank stamps by exactly the traced offset.
- **Chrome flows** — journey-linked spans whose parent lives on a
  different rank emit paired ``ph: s``/``f`` flow events; same-rank
  hops do not.
- **SLO burn rate** — finish-event verdicts land in the sliding
  window; the scrape-time gauge reads violations/total per
  (kind, tenant) and DECAYS to 0.0 (series kept) once verdicts age out.

The true multi-process form (per-rank JSONL files, real clock offsets
over the native TCP plane) is the slow-marked drill at the bottom,
riding ``cluster_worker.py`` with ``CHAINERMN_TPU_JOURNEY_DIR`` set.
"""

import json
import os
import socket
import subprocess
import sys
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from chainermn_tpu.models.transformer import TransformerLM
from chainermn_tpu.observability import clocksync, journey, metrics, trace
from chainermn_tpu.serving import Request, Scheduler, ServingEngine
from chainermn_tpu.serving.cluster import (
    LoopbackHub,
    Router,
    make_replicas,
)
from chainermn_tpu.serving.cluster.tree_push import tree_push

VOCAB = 32


def tiny_lm(**kw):
    cfg = dict(vocab_size=VOCAB, num_layers=2, num_heads=4, d_model=16,
               d_ff=32, max_len=64, compute_dtype=jnp.float32)
    cfg.update(kw)
    return TransformerLM(**cfg)


@pytest.fixture(scope="module")
def lm():
    model = tiny_lm()
    params = model.init(
        jax.random.PRNGKey(1), jnp.zeros((1, 4), jnp.int32), train=False
    )
    return model, params


@pytest.fixture(autouse=True)
def _isolated_plane():
    trace.disable()
    metrics.reset()
    yield
    trace.disable()
    metrics.reset()


ENGINE_KW = dict(num_slots=4, max_len=64, decode_impl="paged",
                 kv_block_size=8, prefill_buckets=(4, 8, 16))


# ----------------------------------------------------------------------
# JourneyContext mechanics
# ----------------------------------------------------------------------


def test_journey_context_linear_chain():
    ctx = journey.new("r1")
    f0 = ctx.begin_hop()
    f1 = ctx.begin_hop()
    f2 = ctx.begin_hop()
    assert f0["span"] == f"{ctx.journey}/0" and "parent" not in f0
    assert f1["parent"] == f0["span"]
    assert f2["parent"] == f1["span"]
    assert f0["journey"] == f1["journey"] == ctx.journey
    # readable prefix + cluster-unique suffix
    assert ctx.journey.startswith("r1@")


def test_journey_ids_unique_across_same_request_id():
    a, b = journey.new("dup"), journey.new("dup")
    assert a.journey != b.journey


def test_wire_roundtrip_continues_not_restarts():
    ctx = journey.new("w")
    first = ctx.begin_hop()
    other = journey.JourneyContext.from_wire(ctx.to_wire())
    nxt = other.begin_hop()
    assert nxt["parent"] == first["span"]
    assert nxt["span"] == f"{ctx.journey}/1"


def test_ensure_is_keep_arrival_sibling():
    req = Request(prompt=[1, 2], max_new_tokens=2)
    ctx = journey.ensure(req)
    assert journey.ensure(req) is ctx  # second front door: no restart


def test_attach_adopt_payload():
    src = Request(prompt=[1], max_new_tokens=2, request_id="x")
    journey.ensure(src).begin_hop()
    payload = journey.attach_payload({"schema": 1}, src)
    dst = Request(prompt=[1], max_new_tokens=2, request_id="x")
    journey.adopt_payload(dst, payload)
    assert journey.fields(dst)["parent"] == src._journey.last_span
    # a journey-less payload leaves the request untouched
    clean = Request(prompt=[1], max_new_tokens=2)
    journey.adopt_payload(clean, {"schema": 1})
    assert clean._journey is None


# ----------------------------------------------------------------------
# Clock sync
# ----------------------------------------------------------------------


def test_estimate_offset_hand_math():
    # one exchange: t0=0, server says 5.0, t1=0.2 -> offset 4.9, ±0.1
    est = clocksync.estimate_offset([(0.0, 5.0, 0.2)])
    assert est["offset_s"] == pytest.approx(4.9)
    assert est["uncertainty_s"] == pytest.approx(0.1)
    assert est["min_rtt_s"] == pytest.approx(0.2)
    # median rejects one polluted exchange
    est = clocksync.estimate_offset(
        [(0.0, 5.0, 0.2), (1.0, 6.0, 1.2), (2.0, 99.0, 2.2)])
    assert est["offset_s"] == pytest.approx(4.9)
    with pytest.raises(ValueError):
        clocksync.estimate_offset([])


def test_loopback_sync_recovers_simulated_skew():
    hub = LoopbackHub()
    e0, e1 = hub.endpoint(0), hub.endpoint(1)
    skew = 0.25  # client runs 250 ms ahead of the server
    rec = trace.enable(None)
    est = clocksync.sync_client(
        e1, 0, n=6,
        pump=lambda: clocksync.sync_server_step(e0, 1),
        clock=lambda: time.time() + skew,
    )
    # offset = server - client = -skew, within the reported error bar
    assert abs(est["offset_s"] + skew) <= est["uncertainty_s"] + 1e-3
    ev = [e for e in rec.events if e["kind"] == "clock_sync"]
    assert len(ev) == 1 and ev[0]["peer"] == 0
    assert ev[0]["offset_s"] == est["offset_s"]
    assert ev[0]["n"] == 6


def test_merge_applies_traced_offset():
    evs = [
        {"schema": 1, "kind": "clock_sync", "t": 0.0, "rank": 1,
         "peer": 0, "offset_s": -2.5, "uncertainty_s": 0.001,
         "min_rtt_s": 0.002, "n": 4},
        {"schema": 1, "kind": "route", "t": 10.0, "rank": 0,
         "journey": "j", "span": "j/0"},
        {"schema": 1, "kind": "serving", "phase": "finish", "t": 13.0,
         "rank": 1, "journey": "j", "span": "j/1", "parent": "j/0"},
    ]
    rep = journey.merge_journeys(evs)
    assert rep["clock"]["offsets"][1]["offset_s"] == -2.5
    assert rep["clock"]["max_uncertainty_s"] == pytest.approx(0.001)
    spans = rep["slowest"][0]["spans"]
    assert spans[0]["t_adj"] == 10.0  # rank 0: no offset traced
    assert spans[1]["t_adj"] == pytest.approx(10.5)  # 13.0 - 2.5


# ----------------------------------------------------------------------
# Decomposition + merge checks (synthetic)
# ----------------------------------------------------------------------


def _chain(jid, rows):
    out = []
    for hop, ev in enumerate(rows):
        ev = dict(ev, journey=jid, span=f"{jid}/{hop}")
        if hop:
            ev["parent"] = f"{jid}/{hop - 1}"
        ev.setdefault("schema", 1)
        ev.setdefault("rank", 0)
        out.append(ev)
    return out


def test_decompose_preempt_gap_attribution():
    evs = _chain("p", [
        {"kind": "route", "t": 0.0},
        {"kind": "serving", "phase": "queue_wait", "t": 1.0,
         "dur_s": 0.1},
        {"kind": "serving", "phase": "preempt", "t": 1.5},
        {"kind": "serving", "phase": "queue_wait", "t": 2.0,
         "dur_s": 0.2},
        {"kind": "serving", "phase": "prefill", "t": 2.5, "dur_s": 0.3,
         "ttft_s": 1.0},
        {"kind": "serving", "phase": "finish", "t": 3.0, "dur_s": 1.4},
    ])
    d = journey.decompose_ttft(evs)
    # 1.0 - (0.3 queue + 0.3 prefill) = 0.4 requeue gap, attributed
    # because a preempt precedes the first token; residual stays ~0
    assert d["queue_wait_s"] == pytest.approx(0.3)
    assert d["prefill_s"] == pytest.approx(0.3)
    assert d["preempt_gap_s"] == pytest.approx(0.4)
    assert abs(d["residual_s"]) < 1e-9
    assert d["preempts_before_first_token"] == 1
    assert d["decode_s"] == pytest.approx(0.4)  # 1.4 total - 1.0 ttft


def test_check_journeys_flags_bad_chains():
    good = _chain("g", [
        {"kind": "serving", "phase": "prefill", "t": 1.0, "dur_s": 0.1,
         "ttft_s": 0.1},
        {"kind": "serving", "phase": "finish", "t": 2.0, "dur_s": 0.2},
    ])
    assert journey.check_journeys(good, expect=1) == []
    # no finish -> incomplete
    assert any("no finish" in p
               for p in journey.check_journeys(good[:1]))
    # hop gap + orphan parent
    gap = [dict(good[0]), dict(good[1], span="g/5", parent="g/4")]
    probs = journey.check_journeys(gap)
    assert any("gaps" in p for p in probs)
    assert any("orphan" in p for p in probs)
    # blown decomposition residual: ttft_s disagrees with components
    bad = _chain("b", [
        {"kind": "serving", "phase": "queue_wait", "t": 0.5,
         "dur_s": 0.5},
        {"kind": "serving", "phase": "prefill", "t": 1.0, "dur_s": 0.1,
         "ttft_s": 0.1},
        {"kind": "serving", "phase": "finish", "t": 2.0, "dur_s": 0.2},
    ])
    assert any("residual" in p for p in journey.check_journeys(bad))
    # wrong journey count
    assert any("expected 2" in p
               for p in journey.check_journeys(good, expect=2))


# ----------------------------------------------------------------------
# Chrome flow arrows
# ----------------------------------------------------------------------


def test_chrome_flow_events_cross_rank_only():
    evs = _chain("f", [
        {"kind": "route", "t": 1.0, "rank": 0},
        {"kind": "kv_transfer", "t": 1.1, "rank": 1, "dur_s": 0.05},
        {"kind": "serving", "phase": "prefill", "t": 1.2, "rank": 1,
         "dur_s": 0.01},
    ])
    ct = trace.chrome_trace(evs)
    flows = [e for e in ct["traceEvents"] if e["ph"] in ("s", "f")]
    # exactly the rank-0 -> rank-1 hop draws an arrow; the same-rank
    # hop 1 -> hop 2 does not
    assert [e["ph"] for e in flows] == ["s", "f"]
    s, f = flows
    assert s["id"] == f["id"] and f["bp"] == "e"
    assert (s["pid"], f["pid"]) == (0, 1)
    assert f["ts"] >= s["ts"]
    assert s["cat"] == f["cat"] == "journey"
    # t_mono is a clock, not an arg — excluded like t itself
    xs = [e for e in ct["traceEvents"] if e["ph"] == "X"]
    assert all("t_mono" not in e["args"] for e in xs)


def test_event_record_carries_t_mono():
    rec = trace.enable(None)
    rec.event("route")
    ev = rec.events[-1]
    assert {"t", "t_mono", "pid", "rank"} <= set(ev)
    assert ev["t_mono"] == pytest.approx(time.perf_counter(), abs=5.0)


# ----------------------------------------------------------------------
# The tier-1 cluster pin: 2-replica disaggregated journeys reconstruct
# ----------------------------------------------------------------------


def test_disaggregated_journeys_reconstruct(lm):
    """Every request through the disaggregated router merges to ONE
    complete causal chain whose decomposition sums to its measured
    TTFT — the acceptance predicate over a real (in-process) cluster
    trace, with the handoff visible as a nonzero component."""
    model, params = lm
    rec = trace.enable(None)
    reps = make_replicas(model, params, 2, **ENGINE_KW)
    router = Router(reps, mode="disaggregated", prefill_replicas=[0])
    rs = np.random.RandomState(7)
    n = 5
    for i in range(n):
        p = rs.randint(1, VOCAB, size=int(rs.randint(2, 6))).tolist()
        router.submit(Request(prompt=p,
                              max_new_tokens=int(rs.randint(2, 5))))
    router.run()
    evs = list(rec.events)
    assert journey.check_journeys(evs, expect=n) == []
    rep = journey.merge_journeys(evs, top=n)
    assert rep["n_complete"] == n and rep["n_orphan_spans"] == 0
    for j in rep["slowest"]:
        d = j["decomposition"]
        # the disaggregated handoff is ON the critical path and billed
        # exactly once (prefill is net of it)
        assert d["handoff_s"] > 0.0
        assert d["queue_wait_s"] >= 0.0 and d["prefill_s"] >= 0.0
        total = (d["queue_wait_s"] + d["prefill_s"] + d["handoff_s"]
                 + d["preempt_gap_s"] + d["residual_s"])
        assert total == pytest.approx(d["ttft_s"], abs=1e-6)
        kinds = [s["kind"] for s in j["spans"]]
        assert kinds[0] == "route" and "kv_transfer" in kinds


def test_recorder_on_off_decode_hlo_identical(lm):
    """The journey plane is host-side by construction: the jitted
    decode program lowers to byte-identical HLO whether the recorder
    (and with it every journey-decorated event site) is off, or on
    with requests actively flowing — the test_trace certificate,
    extended over the ISSUE 17 wiring."""
    model, params = lm

    def decode_hlo(engine):
        n = ENGINE_KW["num_slots"]
        args = (
            engine._cache, engine._vars,
            jnp.zeros((n,), jnp.int32), jnp.zeros((n,), jnp.int32),
            jnp.asarray(engine._dummy_tables()),
            jnp.asarray(engine._seeds),
        )
        return engine._decode_step_jit.lower(*args).compile().as_text()

    off = decode_hlo(ServingEngine(model, params, **ENGINE_KW))
    rec = trace.enable(None)
    engine = ServingEngine(model, params, **ENGINE_KW)
    sched = Scheduler(engine)
    sched.submit(Request(prompt=[3, 5, 7], max_new_tokens=3))
    sched.run()
    assert any("journey" in e for e in rec.events)  # plane was live
    assert decode_hlo(engine) == off


def test_preempted_journey_stays_one_chain(lm):
    """Preemption extends the chain (route -> ... -> preempt -> route
    -> ...) instead of forking it: one journey id, contiguous hops,
    decomposition still sums (gap attributed)."""
    model, params = lm
    rec = trace.enable(None)
    reps = make_replicas(model, params, 2, **ENGINE_KW)
    router = Router(reps, mode="colocated", policy="least_loaded")
    rs = np.random.RandomState(11)
    p = rs.randint(1, VOCAB, size=4).tolist()
    rid = router.submit(Request(prompt=p, max_new_tokens=4))
    # drive the holding replica until the request is in flight, then
    # migrate it to the other replica
    src = next(i for i, rep in router.replicas.items()
               if rep.load() > 0)
    for _ in range(2):
        router.replicas[src].tick()
    dst = router.preempt_request(rid)
    assert dst != src
    router.run()
    evs = list(rec.events)
    mine = [e for e in evs if e.get("journey")
            and str(e["journey"]).startswith(f"{rid}@")]
    jids = {e["journey"] for e in mine}
    assert len(jids) == 1  # migration did NOT restart the chain
    assert journey.check_journeys(evs, expect=1) == []
    assert sum(1 for e in mine if e["kind"] == "route") >= 2
    assert any(e.get("phase") == "preempt" for e in mine)


# ----------------------------------------------------------------------
# tree_push journey hops
# ----------------------------------------------------------------------


def test_tree_push_continues_or_mints_journey():
    hub = LoopbackHub()
    endpoints = {r: hub.endpoint(r) for r in range(3)}
    rec = trace.enable(None)
    # dict payload WITHOUT a journey: the push mints one
    tree_push({"schema": 1}, endpoints, [0, 1, 2],
              payload_kind="adapter")
    ev = [e for e in rec.events if e["kind"] == "tree_push"][-1]
    assert ev["journey"].startswith("adapter-push@")
    assert ev["span"].endswith("/0")
    # payload WITH a journey: the push parents onto the carried span
    src = Request(prompt=[1], max_new_tokens=2, request_id="warm")
    prior = journey.fields(src)
    payload = journey.attach_payload({"schema": 1}, src)
    tree_push(payload, endpoints, [0, 1, 2], payload_kind="kv_warm")
    ev2 = [e for e in rec.events if e["kind"] == "tree_push"][-1]
    assert ev2["journey"] == prior["journey"]
    assert ev2["parent"] == prior["span"]
    # receivers hold the ADVANCED snapshot: adopting it parents onto
    # the push's own span
    dst = Request(prompt=[1], max_new_tokens=2)
    journey.adopt_payload(dst, payload)
    assert journey.fields(dst)["parent"] == ev2["span"]


# ----------------------------------------------------------------------
# SLO burn-rate gauges
# ----------------------------------------------------------------------


def test_slo_burn_rate_gauge_from_finish_events():
    reg = metrics.install_tap()
    rec = trace.enable(None)
    rec.event("serving", phase="finish", dur_s=0.1, slo_ttft_ok=True,
              slo_tpot_ok=True)
    rec.event("serving", phase="finish", dur_s=0.1, slo_ttft_ok=False,
              slo_tpot_ok=True)
    rec.event("serving", phase="finish", dur_s=0.1, slo_ttft_ok=False,
              slo_tpot_ok=False, tenant="acme")
    rec.event("serving", phase="finish", dur_s=0.1)  # no targets: no row
    burn = metrics.slo_burn_rates()
    assert burn == {
        "ttft": {"acme": 1.0, "default": 0.5},
        "tpot": {"acme": 1.0, "default": 0.0},
    }
    snap = reg.snapshot()
    rows = {tuple(sorted(v["labels"].items())): v["value"]
            for v in snap["serving_slo_burn_rate"]["values"]}
    assert rows[(("kind", "ttft"), ("tenant", "default"))] == 0.5
    assert rows[(("kind", "tpot"), ("tenant", "acme"))] == 1.0


def test_slo_burn_rate_decays_but_series_stays():
    metrics.install_tap()
    rec = trace.enable(None)
    rec.event("serving", phase="finish", dur_s=0.1, slo_ttft_ok=False)
    assert metrics.slo_burn_rates()["ttft"]["default"] == 1.0
    time.sleep(0.02)
    # verdicts older than the window age out; the pair still exports
    # 0.0 (a vanished series and a healthy one must not look alike)
    burn = metrics.slo_burn_rates(window_s=0.01)
    assert burn == {"ttft": {"default": 0.0}}


def test_slo_window_env_rule(monkeypatch):
    assert metrics._slo_window_s() == 60.0
    monkeypatch.setenv("CHAINERMN_TPU_SLO_WINDOW_S", "5")
    assert metrics._slo_window_s() == 5.0
    monkeypatch.setenv("CHAINERMN_TPU_SLO_WINDOW_S", "bogus")
    assert metrics._slo_window_s() == 60.0
    monkeypatch.setenv("CHAINERMN_TPU_SLO_WINDOW_S", "-3")
    assert metrics._slo_window_s() == 60.0


# ----------------------------------------------------------------------
# The multi-process drill (slow): real processes, real clock offsets
# ----------------------------------------------------------------------

SLOW_WORKER = Path(__file__).resolve().parent / "cluster_worker.py"


@pytest.mark.slow
@pytest.mark.multiprocess
def test_mp_journey_merge_over_tcp(tmp_path):
    """The true cross-PROCESS journey: per-rank JSONL files, a real
    clock-sync exchange over the TCP plane, KV payloads carrying the
    journey wire key — merged afterwards, every request must
    reconstruct to one complete causal chain spanning both pids, with
    flow arrows in the Chrome export."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["CHAINERMN_TPU_JOURNEY_DIR"] = str(tmp_path)
    procs = [
        subprocess.Popen(
            [sys.executable, str(SLOW_WORKER), str(r), "2",
             f"127.0.0.1:{port}"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env,
            cwd=str(SLOW_WORKER.parent.parent),
        )
        for r in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out}"
        assert f"CLUSTER_WORKER_OK {r}" in out

    evs = []
    for r in range(2):
        evs.extend(trace.read_jsonl(str(tmp_path / f"rank{r}.jsonl")))
    assert journey.check_journeys(evs, expect=4) == []
    rep = journey.merge_journeys(evs, top=4)
    assert rep["n_complete"] == 4
    # the clock-sync rode the same TCP plane: rank 1 traced its offset
    off = rep["clock"]["offsets"]
    assert 1 in off and off[1]["peer"] == 0
    assert off[1]["uncertainty_s"] > 0.0
    for j in rep["slowest"]:
        assert j["ranks"] == [0, 1] and len(j["pids"]) == 2
        assert j["decomposition"]["handoff_s"] > 0.0
    # cross-pid hops draw flow arrows in the Chrome export
    ct = trace.chrome_trace(evs)
    flows = [e for e in ct["traceEvents"] if e["ph"] in ("s", "f")]
    assert len(flows) == 2 * 4  # one s/f pair per request's handoff
